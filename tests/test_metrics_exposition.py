"""Prometheus exposition-format lint over the platform's combined registry.

A strict scraper rejects an entire /metrics page for one malformed line —
an invalid label escape or duplicate family silently blinds every dashboard
at once. This suite scrapes the REAL combined registry (notebook + scheduler
+ control-plane families sharing one Registry, exactly the wiring
``cmd/controller.py`` ships) through a small grammar validator:

- every line is a well-formed HELP/TYPE/sample;
- one HELP+TYPE per family, no duplicate families;
- sample names belong to their family (histograms: ``_bucket``/``_sum``/
  ``_count`` suffixes only);
- label values parse under exposition escaping rules;
- histogram buckets are cumulative-monotone, carry ``le="+Inf"``, and the
  +Inf bucket equals ``_count``.

Run as the metrics-lint step in ``unit_tests.yaml``.
"""
from __future__ import annotations

import re

import pytest

from kubeflow_tpu.api import types as api
from kubeflow_tpu.obs import EventRecorder, Tracer
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.runtime.manager import Manager
from kubeflow_tpu.utils.metrics import (
    ControlPlaneMetrics,
    NotebookMetrics,
    Registry,
    SchedulerMetrics,
    SessionMetrics,
)

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# a sample line: name[{labels}] value  — labels parsed separately
SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)$"
)
# one label under exposition escaping: value may contain \\, \", \n escapes
LABEL_RE = re.compile(
    r'^(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\["\\n])*)"'
)


def parse_exposition(text: str) -> dict[str, dict]:
    """Validating parser: returns {family: {"type", "help", "samples":
    [(name, labels, value)]}}; raises AssertionError on any grammar breach."""
    families: dict[str, dict] = {}
    current: str | None = None
    assert text.endswith("\n"), "exposition must end with a newline"
    for lineno, line in enumerate(text.splitlines(), 1):
        where = f"line {lineno}: {line!r}"
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            assert NAME_RE.match(name), f"bad HELP name, {where}"
            assert name not in families, f"duplicate family {name}, {where}"
            families[name] = {"help": help_text, "type": None, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert name == current, f"TYPE before/without its HELP, {where}"
            assert kind in ("counter", "gauge", "histogram"), where
            assert families[name]["type"] is None, f"duplicate TYPE, {where}"
            families[name]["type"] = kind
            continue
        assert not line.startswith("#"), f"unknown comment, {where}"
        m = SAMPLE_RE.match(line)
        assert m, f"unparseable sample, {where}"
        sname = m.group("name")
        assert current is not None, f"sample before any family, {where}"
        fam = families[current]
        if fam["type"] == "histogram":
            assert (
                sname == current + "_bucket"
                or sname == current + "_sum"
                or sname == current + "_count"
            ), f"sample {sname} not a {current} histogram series, {where}"
        else:
            assert sname == current, (
                f"sample {sname} outside family {current}, {where}"
            )
        labels: dict[str, str] = {}
        raw = m.group("labels")
        while raw:
            lm = LABEL_RE.match(raw)
            assert lm, f"bad label syntax at {raw!r}, {where}"
            labels[lm.group("name")] = lm.group("value")
            raw = raw[lm.end():]
            if raw.startswith(","):
                raw = raw[1:]
        value = float(m.group("value"))  # ValueError = invalid sample
        fam["samples"].append((sname, labels, value))
    for name, fam in families.items():
        assert fam["type"] is not None, f"family {name} missing TYPE"
    return families


def check_histograms(families: dict[str, dict]) -> None:
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        # group by non-le label set
        series: dict[tuple, dict] = {}
        for sname, labels, value in fam["samples"]:
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            row = series.setdefault(
                key, {"buckets": [], "sum": None, "count": None}
            )
            if sname.endswith("_bucket"):
                row["buckets"].append((labels["le"], value))
            elif sname.endswith("_sum"):
                row["sum"] = value
            else:
                row["count"] = value
        for key, row in series.items():
            assert row["buckets"], f"{name}{key}: no buckets"
            assert row["buckets"][-1][0] == "+Inf", (
                f"{name}{key}: last bucket must be +Inf"
            )
            counts = [v for _, v in row["buckets"]]
            assert counts == sorted(counts), (
                f"{name}{key}: buckets not cumulative-monotone: {counts}"
            )
            bounds = [float(le) for le, _ in row["buckets"][:-1]]
            assert bounds == sorted(bounds), (
                f"{name}{key}: bucket bounds not increasing"
            )
            assert row["count"] is not None and row["sum"] is not None, (
                f"{name}{key}: missing _sum/_count"
            )
            assert row["count"] == counts[-1], (
                f"{name}{key}: +Inf bucket {counts[-1]} != count {row['count']}"
            )


def combined_registry() -> Registry:
    """The full production wiring: one registry, every family, populated by
    actually running the control plane (not by poking counters)."""
    from kubeflow_tpu.obs.slo import SLOMetrics
    from kubeflow_tpu.obs.timeline import TimelineRecorder

    from kubeflow_tpu.utils.metrics import CapacityMetrics

    nm = NotebookMetrics()
    sm = SchedulerMetrics(nm.registry)
    cpm = ControlPlaneMetrics(nm.registry)
    sessm = SessionMetrics(nm.registry)
    slo = SLOMetrics(nm.registry)
    capm = CapacityMetrics(nm.registry)
    # every capacity family populated so the exposition lint sees samples
    capm.scale_ups.inc(family="v4", tier="spot")
    capm.scale_downs.inc(family="v4")
    capm.revocations.inc(family="v4")
    capm.provider_errors.inc(op="scale_up")
    capm.open_requests.set(1.0)
    capm.pending_chips.set(16.0, family="v4")
    capm.decision_latency.observe(2.0)
    capm.observe_first_chip(42.0)
    wq_gauge = nm.registry.gauge(
        "workqueue_stat", "Reconcile workqueue counters (native core)"
    )

    from kubeflow_tpu.controllers.notebook_controller import NotebookReconciler
    from kubeflow_tpu.scheduler.controller import SchedulerReconciler
    from kubeflow_tpu.sessions.controller import SessionReconciler
    from kubeflow_tpu.sessions.store import SnapshotStore
    from kubeflow_tpu.testing.sessionstore import (
        FakeObjectStore,
        FakeSessionAgent,
    )
    from kubeflow_tpu.utils.config import ControllerConfig

    cluster = FakeCluster()
    cluster.add_tpu_node_pool("v4", "2x2x2")
    tracer = Tracer()
    mgr = Manager(cluster, tracer=tracer, metrics=cpm)
    cfg = ControllerConfig(scheduler_enabled=True, sessions_enabled=True)
    mgr.register(
        NotebookReconciler(
            cfg, metrics=nm, recorder=EventRecorder(),
            timeline=TimelineRecorder(slo=slo),
        )
    )
    mgr.register(
        SchedulerReconciler(
            metrics=sm, recorder=EventRecorder(),
            suspend_deadline_s=cfg.suspend_deadline_s,
        )
    )
    mgr.register(
        SessionReconciler(
            SnapshotStore(FakeObjectStore()), FakeSessionAgent(cluster),
            config=cfg, metrics=sessm, recorder=EventRecorder(),
        )
    )
    cluster.create(
        api.notebook("nb-lint", "team-metrics", tpu_accelerator="v4",
                     tpu_topology="2x2x2")
    )
    cluster.settle(mgr, rounds=4)
    # a second gang the now-held pool cannot take: the explainability
    # families (scheduler/explain.py — reason counters, fragmentation
    # gauges, per-family queue depth) get real observations, not vacuous
    # zeros; the stop below frees the pool, so its verdict also closes out
    # into the time-in-reason histogram
    cluster.create(
        api.notebook("nb-blocked", "team-metrics", tpu_accelerator="v4",
                     tpu_topology="2x2x2")
    )
    cluster.settle(mgr, rounds=4)
    # data-plane telemetry on the same registry (telemetry/collector.py):
    # one scrape pass against a fake agent populates every family
    from kubeflow_tpu.culler.probe import ProbeResult
    from kubeflow_tpu.runtime import objects as ko
    from kubeflow_tpu.telemetry.agent import FakeDeviceBackend, TelemetryAgent
    from kubeflow_tpu.telemetry.collector import FleetTelemetryCollector
    from kubeflow_tpu.utils.metrics import TelemetryMetrics

    agent = TelemetryAgent(FakeDeviceBackend(duty_cycle=0.5))
    telem = FleetTelemetryCollector(
        cluster, TelemetryMetrics(nm.registry),
        probe_fn=lambda targets, **kw: [
            ProbeResult(200, agent.exposition()) for _ in targets
        ],
        target_for=lambda nb: (ko.namespace(nb), 0, ko.name(nb)),
        tracer=tracer,
    )
    telem.collect(force=True)
    telem.record_cull(
        "team-metrics", "nb-lint", policy="duty-cycle",
        sample=telem.activity("team-metrics", "nb-lint"), threshold=0.6,
    )
    # gang step telemetry on the same registry (telemetry/gang.py): two
    # scrape passes over the 2-host gang with a planted slow host populate
    # every gang family with real judgments (nb-blocked's hosts have no
    # agent, so the failed-scrape outcome gets samples too)
    from kubeflow_tpu.telemetry.agent import FakeStepSchedule
    from kubeflow_tpu.telemetry.gang import GangTelemetryAggregator, host_key
    from kubeflow_tpu.utils.metrics import GangMetrics

    _g = [1_000_000.0]
    gang_agents = {
        host_key("nb-lint", 0, o, 1): TelemetryAgent(
            FakeDeviceBackend(duty_cycle=0.9, seed=o),
            clock=lambda: _g[0],
            step_schedule=FakeStepSchedule(
                period_s=6.0, duration_s=2.5, start_at=_g[0] - 200.0,
                seed=o, slow_factor=2.0 if o == 1 else 1.0,
            ),
        )
        for o in range(2)
    }
    gang = GangTelemetryAggregator(
        cluster, GangMetrics(nm.registry), min_steps=3,
        clock=lambda: _g[0],
        probe_fn=lambda targets, **kw: [
            ProbeResult(200, gang_agents[hk].exposition())
            if hk in gang_agents else ProbeResult(-1, "")
            for hk, _port, _path in targets
        ],
        target_for=lambda nb, j, o: (
            host_key(ko.name(nb), j, o, api.notebook_num_slices(nb)), 0, "/"
        ),
    )
    gang.collect(force=True)
    _g[0] += 10.0
    gang.collect(force=True)
    assert gang.audit() == []
    # the efficiency ledger on the same registry (obs/ledger.py): two real
    # ticks over a moving clock populate every bucket/capacity family
    from kubeflow_tpu.obs.ledger import FleetEfficiencyLedger
    from kubeflow_tpu.utils.metrics import LedgerMetrics

    _t = [1_000_000.0]
    ledger = FleetEfficiencyLedger(
        cluster, LedgerMetrics(nm.registry), clock=lambda: _t[0],
        telemetry=telem,
    )
    ledger.tick(force=True)
    _t[0] += 30.0
    ledger.tick(force=True)
    assert ledger.audit() == []
    # one suspend through the barrier so the session histograms carry data
    cluster.patch("Notebook", "nb-lint", "team-metrics",
                  {"metadata": {"annotations": {
                      "kubeflow-resource-stopped": "2026-01-01T00:00:00Z"}}})
    cluster.settle(mgr, rounds=4)
    for k, v in mgr.queue_metrics().items():
        wq_gauge.set(float(v), stat=k)
    return nm.registry


class TestExpositionFormat:
    def test_combined_registry_is_valid(self):
        registry = combined_registry()
        families = parse_exposition(registry.expose())
        check_histograms(families)
        # the acceptance-criteria families are present as histograms
        for name in (
            "controller_reconcile_duration_seconds",
            "workqueue_queue_wait_seconds",
            "scheduler_time_to_bind_seconds",
            "session_suspend_seconds",
            "session_resume_seconds",
            "session_startup_seconds",
            "session_startup_phase_seconds",
            "capacity_time_to_first_chip_seconds",
        ):
            assert families[name]["type"] == "histogram", name
        # the SLO families (obs/slo.py) ride the same registry: the burn
        # gauges and objective counter must lint alongside the histograms
        assert families["slo_startup_burn_rate"]["type"] == "gauge"
        assert families[
            "slo_startup_error_budget_remaining"]["type"] == "gauge"
        assert families["slo_startup_total"]["type"] == "counter"
        # the settle drove the gang to ready: the startup histogram carries
        # the click-to-ready observation (the lint is not vacuous)
        assert any(
            v > 0
            for s, _, v in families["session_startup_seconds"]["samples"]
            if s.endswith("_count")
        )
        # the settle's stop ran the suspend barrier end to end: the suspend
        # histogram must carry the observation
        assert any(
            v > 0
            for s, _, v in families["session_suspend_seconds"]["samples"]
            if s.endswith("_count")
        )
        # ... and actually carry observations from the settle above
        assert any(
            v > 0
            for s, _, v in families[
                "controller_reconcile_duration_seconds"]["samples"]
            if s.endswith("_count")
        )
        assert families["apiserver_request_duration_seconds"]["type"] == (
            "histogram"
        )
        # placement explainability (scheduler/explain.py): verdict-reason
        # counter, time-in-reason histogram, and the fragmentation /
        # queue-depth gauges all lint AND carry the blocked gang's data
        assert families["scheduler_unschedulable_total"]["type"] == "counter"
        assert any(
            labels.get("reason") == "InsufficientCapacity" and v >= 1
            for _, labels, v in families[
                "scheduler_unschedulable_total"]["samples"]
        )
        assert families["scheduler_time_in_reason_seconds"]["type"] == (
            "histogram"
        )
        # nb-blocked bound after the suspend freed the pool: its verdict
        # closed out into the histogram
        assert any(
            v >= 1
            for s, _, v in families[
                "scheduler_time_in_reason_seconds"]["samples"]
            if s.endswith("_count")
        )
        assert any(
            labels == {"family": "v4"}
            for _, labels, _ in families[
                "scheduler_family_queue_depth"]["samples"]
        )
        assert families["scheduler_pool_fragmentation_index"]["type"] == (
            "gauge"
        )
        assert families[
            "scheduler_pool_largest_free_cuboid_chips"]["type"] == "gauge"
        assert families["scheduler_would_fit_after_defrag"]["type"] == "gauge"
        # efficiency-ledger families (obs/ledger.py): the chip-second
        # counters lint and carry real attribution — and conservation is
        # queryable straight off the exposition: Σ pool buckets == capacity
        for name in (
            "tpu_chip_seconds_total",
            "tpu_pool_chip_seconds_total",
            "tpu_family_chip_seconds_total",
            "tpu_capacity_chip_seconds_total",
            "tpu_queued_chip_seconds_total",
            "tpu_ledger_ticks_total",
        ):
            assert families[name]["type"] == "counter", name
        assert families["tpu_fleet_efficiency"]["type"] == "gauge"
        assert families["tpu_fleet_waste_fraction"]["type"] == "gauge"
        assert families["tpu_ledger_tick_seconds"]["type"] == "histogram"
        by_pool: dict[str, float] = {}
        for _, labels, v in families["tpu_pool_chip_seconds_total"]["samples"]:
            by_pool[labels["pool"]] = by_pool.get(labels["pool"], 0.0) + v
        caps = {
            labels["pool"]: v
            for _, labels, v in families[
                "tpu_capacity_chip_seconds_total"]["samples"]
        }
        assert caps and by_pool == caps  # exact — the scrape-side proof
        # gang step-telemetry families (telemetry/gang.py): the per-gang
        # step histogram lints with real observations and the planted slow
        # host's judgment reaches the exposition
        assert families["tpu_gang_step_seconds"]["type"] == "histogram"
        assert families["tpu_gang_pass_seconds"]["type"] == "histogram"
        assert any(
            v > 0
            for s, _, v in families["tpu_gang_step_seconds"]["samples"]
            if s.endswith("_count")
        )
        for name in (
            "tpu_gang_step_skew_seconds",
            "tpu_gang_straggler_ratio",
            "tpu_gang_host_step_lag",
            "tpu_gang_fleet_step_p99_seconds",
            "tpu_gang_fleet_straggler_ratio",
            "tpu_gang_sessions",
        ):
            assert families[name]["type"] == "gauge", name
        assert families["tpu_gang_scrape_total"]["type"] == "counter"
        assert families["tpu_gang_finding_total"]["type"] == "counter"
        for outcome in ("ok", "failed"):
            assert any(
                labels == {"outcome": outcome} and v >= 1
                for _, labels, v in families["tpu_gang_scrape_total"]["samples"]
            ), outcome
        assert any(
            labels.get("kind") == "straggler" and v >= 1
            for _, labels, v in families["tpu_gang_finding_total"]["samples"]
        )
        assert any(
            labels.get("notebook") == "nb-lint" and v >= 1.5
            for _, labels, v in families["tpu_gang_straggler_ratio"]["samples"]
        )

    def test_webapp_and_readcache_families_lint(self):
        """The BFF read-path families (utils/metrics.py WebAppMetrics +
        webapps/cache.py): a served-and-revalidated JWA on the combined
        registry exposes webapp_request_seconds{route,status} plus the
        cache hit/staleness/watch families, all grammar-valid."""
        from werkzeug.test import Client

        from kubeflow_tpu.auth.rbac import Authorizer
        from kubeflow_tpu.webapps import jupyter

        nm = NotebookMetrics()
        ControlPlaneMetrics(nm.registry)
        cluster = FakeCluster()
        app = jupyter.create_app(
            cluster, authorizer=Authorizer(cluster, cluster_admins={"m@x"}),
            metrics=nm,
        )
        client = Client(app)
        headers = {"kubeflow-userid": "m@x"}
        cluster.create(api.notebook("lint-nb", "lint-ns"))
        first = client.get("/api/namespaces/lint-ns/notebooks", headers=headers)
        client.get(
            "/api/namespaces/lint-ns/notebooks",
            headers={**headers, "If-None-Match": first.headers["ETag"]},
        )
        families = parse_exposition(nm.registry.expose())
        check_histograms(families)
        assert families["webapp_request_seconds"]["type"] == "histogram"
        for name in (
            "webapp_responses_not_modified_total",
            "webapp_responses_gzipped_total",
            "webapp_cache_reads_total",
            "webapp_cache_relists_total",
            "webapp_cache_watch_events_total",
        ):
            assert families[name]["type"] == "counter", name
        for name in ("webapp_cache_objects", "webapp_cache_staleness_seconds"):
            assert families[name]["type"] == "gauge", name
        # the histogram carries the served requests, labeled by route
        # pattern and status — and the 304 counted as such
        samples = families["webapp_request_seconds"]["samples"]
        assert any(
            l.get("route") == "/api/namespaces/<namespace>/notebooks"
            and l.get("status") == "304"
            for s, l, v in samples
            if s.endswith("_count") and v > 0
        )
        assert any(
            l.get("kind") == "Notebook" and l.get("source") == "cache" and v > 0
            for _, l, v in families["webapp_cache_reads_total"]["samples"]
        )
        app.close()

    def test_no_duplicate_families_with_web_apps(self):
        # two Apps + the domain registries on one registry (the ops-port
        # sharing pattern): still one HELP/TYPE per family
        from kubeflow_tpu.webapps.base import App

        nm = NotebookMetrics()
        ControlPlaneMetrics(nm.registry)
        App("one", csrf_protect=False, metrics_registry=nm.registry)
        App("two", csrf_protect=False, metrics_registry=nm.registry)
        parse_exposition(nm.registry.expose())

    def test_escaping_round_trips(self):
        reg = Registry()
        g = reg.gauge("weird", "label escape test", labelnames=("v",))
        hostile = 'quote:" backslash:\\ newline:\nend'
        g.set(1, v=hostile)
        families = parse_exposition(reg.expose())
        ((_, labels, _),) = families["weird"]["samples"]
        unescaped = (
            labels["v"]
            .replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
        )
        assert unescaped == hostile

    def test_empty_labeled_family_emits_no_bogus_sample(self):
        reg = Registry()
        reg.counter("labeled_total", "never observed", labelnames=("ns",))
        families = parse_exposition(reg.expose())
        assert families["labeled_total"]["samples"] == []

    def test_empty_unlabeled_family_still_exposes_zero(self):
        reg = Registry()
        reg.counter("bare_total", "zero-valued")
        families = parse_exposition(reg.expose())
        assert families["bare_total"]["samples"] == [("bare_total", {}, 0.0)]


class TestLabelDiscipline:
    def test_mismatched_labels_raise_clear_error(self):
        reg = Registry()
        c = reg.counter("c_total", "h", labelnames=("namespace",))
        c.inc(namespace="a")
        with pytest.raises(ValueError, match="c_total.*namespace"):
            c.inc(pod="p")  # wrong label name
        with pytest.raises(ValueError, match="c_total"):
            c.inc()  # missing label

    def test_first_use_freezes_schema_without_declaration(self):
        reg = Registry()
        g = reg.gauge("g", "h")
        g.set(1, zone="a")
        with pytest.raises(ValueError):
            g.set(2)  # unlabeled after labeled first use

    def test_histogram_rejects_counter_verbs(self):
        reg = Registry()
        h = reg.histogram("h_seconds", "h")
        with pytest.raises(TypeError):
            h.inc()
        with pytest.raises(TypeError):
            h.set(1)


class TestHistogramSemantics:
    def test_bucket_boundaries_are_le_inclusive(self):
        reg = Registry()
        h = reg.histogram("h_seconds", "h", buckets=(0.1, 1.0))
        h.observe(0.1)  # exactly on a bound → that bucket (le semantics)
        families = parse_exposition(reg.expose())
        samples = {
            (s, l.get("le")): v
            for s, l, v in families["h_seconds"]["samples"]
        }
        assert samples[("h_seconds_bucket", "0.1")] == 1

    def test_quantile_estimation(self):
        reg = Registry()
        h = reg.histogram("q_seconds", "h", buckets=(1.0, 2.0, 4.0, 8.0))
        for v in (0.5, 1.5, 3.0, 7.0):
            h.observe(v)
        assert 0.0 < h.quantile(0.5) <= 2.0
        assert h.quantile(0.99) <= 8.0
        assert h.count() == 4
        assert h.sum() == pytest.approx(12.0)

    def test_quantile_empty_histogram_is_zero(self):
        """The SLO/bench consumers divide by quantiles: an empty histogram
        must read 0.0, not raise or return garbage — before ANY observation
        and for a never-observed label set of a populated family."""
        reg = Registry()
        h = reg.histogram("e_seconds", "h", buckets=(1.0, 2.0))
        assert h.quantile(0.5) == 0.0
        assert h.quantile(0.99) == 0.0
        labeled = reg.histogram(
            "l_seconds", "h", labelnames=("kind",), buckets=(1.0, 2.0)
        )
        labeled.observe(0.5, kind="a")
        assert labeled.quantile(0.99, kind="never-observed") == 0.0

    def test_quantile_in_first_bucket_interpolates_from_zero(self):
        """q landing in the first bucket interpolates on [0, bound), never
        below 0 and never the whole bound for a tiny rank."""
        reg = Registry()
        h = reg.histogram("f_seconds", "h", buckets=(10.0, 20.0))
        for _ in range(4):
            h.observe(5.0)
        # all 4 observations in [0, 10): p50 = rank 2 of 4 → 5.0 exactly
        assert h.quantile(0.5) == pytest.approx(5.0)
        assert 0.0 < h.quantile(0.01) < 10.0
        assert h.quantile(1.0) == pytest.approx(10.0)

    def test_quantile_in_inf_bucket_clamps_to_highest_finite_bound(self):
        """q landing in the +Inf bucket must clamp to the largest finite
        bound — returning inf would poison every SLO gauge and dashboard
        series that divides by or charts the value."""
        import math

        reg = Registry()
        h = reg.histogram("i_seconds", "h", buckets=(1.0, 2.0, 4.0))
        h.observe(100.0)   # only observation: the +Inf bucket
        h.observe(1000.0)
        for q in (0.01, 0.5, 0.99, 1.0):
            v = h.quantile(q)
            assert math.isfinite(v)
            assert v == pytest.approx(4.0)
        # mixed: p99 still clamps while p25 interpolates a finite bucket
        h.observe(0.5)
        h.observe(0.6)
        assert h.quantile(0.99) == pytest.approx(4.0)
        assert 0.0 < h.quantile(0.25) <= 1.0

    def test_time_to_bind_exposes_sum_and_count(self):
        """ISSUE satellite: rate(sum)/rate(count) must be possible — the old
        sum-only counter exposed no _count at all."""
        sm = SchedulerMetrics()
        sm.observe_bind(12.0)
        sm.observe_bind(700.0)
        text = sm.registry.expose()
        families = parse_exposition(text)
        samples = {
            s: v
            for s, _, v in families["scheduler_time_to_bind_seconds"]["samples"]
            if not s.endswith("_bucket")
        }
        assert samples["scheduler_time_to_bind_seconds_count"] == 2
        assert samples["scheduler_time_to_bind_seconds_sum"] == (
            pytest.approx(712.0)
        )
        assert sm.bind_seconds_max.get() == 700.0


class TestShardLabel:
    """Control-plane sharding (runtime/sharding.py): N shard managers share
    ONE registry, so the per-manager families carry a ``shard`` label —
    without it, gauges last-writer-win across shards and counters
    double-count into one series. The unsharded schema stays label-free
    (``SHARDS=1`` exposition is byte-identical to pre-sharding)."""

    def test_sharded_families_on_one_registry_do_not_collide(self):
        registry = Registry()
        cps = [ControlPlaneMetrics(registry, shard=str(i)) for i in range(4)]
        sms = [SchedulerMetrics(registry, shard=str(i)) for i in range(4)]
        for i in range(4):
            cps[i].observe_reconcile("Notebook", 0.01 * (i + 1), "success")
            cps[i].queue_retries.inc()
            sms[i].queue_depth.set(float(10 + i))
            sms[i].observe_bind(1.0 + i)
            sms[i].preemptions.inc()
        families = parse_exposition(registry.expose())
        check_histograms(families)
        # one family each (no duplicates — the parser asserts that), with
        # four disjoint per-shard series
        depth = {
            labels["shard"]: value
            for _, labels, value in families["scheduler_queue_depth"]["samples"]
        }
        assert depth == {"0": 10.0, "1": 11.0, "2": 12.0, "3": 13.0}
        retries = families["workqueue_retries_total"]["samples"]
        assert len(retries) == 4
        assert all(value == 1.0 for _, _, value in retries)
        binds = {
            labels["shard"]: value
            for name, labels, value in families[
                "scheduler_time_to_bind_seconds"]["samples"]
            if name.endswith("_count")
        }
        assert binds == {"0": 1.0, "1": 1.0, "2": 1.0, "3": 1.0}
        # per-kind labels compose with the shard label on one series
        recon = {
            (labels["kind"], labels["outcome"], labels["shard"])
            for _, labels, _ in families["controller_reconcile_total"]["samples"]
        }
        assert ("Notebook", "success", "2") in recon
        # bound-metric reads see their own shard's series only
        assert sms[1].queue_depth.get() == 11.0
        assert sms[3].bind_seconds_max.get() == 4.0

    def test_unsharded_schema_is_unchanged(self):
        registry = Registry()
        sm = SchedulerMetrics(registry)
        sm.queue_depth.set(3)
        families = parse_exposition(registry.expose())
        (sample,) = families["scheduler_queue_depth"]["samples"]
        assert sample[1] == {}  # no shard label in the single-loop plane

    def test_mixing_sharded_and_unsharded_instances_raises(self):
        """A sharded and an unsharded collector on one registry is a wiring
        error — it must fail LOUDLY at registration (families with declared
        labelnames, e.g. the phase histogram) or at the first observation
        (families whose schema froze on first use), never by silently
        corrupting series. The delayed-error variant let a soak run a
        crash-every-cycle scheduler while its audits looked green."""
        registry = Registry()
        SchedulerMetrics(registry, shard="0").queue_depth.set(1)
        with pytest.raises(ValueError):
            # cycle_phase is declared ("phase",) unsharded vs
            # ("phase","shard") sharded: registration itself conflicts
            plain = SchedulerMetrics(registry)
            plain.queue_depth.set(2)  # and first use would too
        registry2 = Registry()
        ControlPlaneMetrics(registry2)  # unsharded first, never observed
        with pytest.raises(ValueError):
            ControlPlaneMetrics(registry2, shard="1")
