"""Fleet efficiency ledger (obs/ledger.py, docs/observability.md
"efficiency ledger"): bucket taxonomy, exact conservation, exactly-once
intervals across crash-restart windows, the audit's misattribution
detection, the /debug/ledger routes, and the JWA/dashboard surfaces.

The exactness claims here are deliberate ``==`` on integers and on the
float projections the ledger itself exports — the conservation invariant is
"no epsilon", so the tests must not soften it with approx."""
from __future__ import annotations

import json

from werkzeug.test import Client

from kubeflow_tpu import scheduler as sched
from kubeflow_tpu import sessions as sess
from kubeflow_tpu.api import types as api
from kubeflow_tpu.obs import timeline as tl
from kubeflow_tpu.obs.ledger import (
    BUCKET_BUSY,
    BUCKET_DRAINING,
    BUCKET_FREE_STRANDED,
    BUCKET_FREE_USABLE,
    BUCKET_IDLE,
    BUCKET_PARKED,
    BUCKET_STARTING,
    BUCKET_SUSPENDING,
    BUCKET_UNAVAILABLE,
    CONSERVATION_BUCKETS,
    FleetEfficiencyLedger,
    classify_gang,
    install_ledger_routes,
)
from kubeflow_tpu.runtime.fake import FakeCluster
from kubeflow_tpu.scheduler.soak import make_pool
from kubeflow_tpu.utils.metrics import LedgerMetrics
from kubeflow_tpu.webapps.base import App

NS = "team-a"


class FakeClock:
    def __init__(self, t: float = 1_000_000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


class FakeTelemetry:
    """The collector surface the ledger reads: activity(ns, name)."""

    def __init__(self, duties: dict | None = None) -> None:
        self.duties = duties or {}

    def activity(self, namespace: str, name: str):
        duty = self.duties.get(name)
        if duty is None:
            return None

        class _S:
            duty_cycle = duty

        return _S()


def _world(pools=(("v4", "2x2x4", "pool-a"),)):
    cluster = FakeCluster()
    for accel, topo, name in pools:
        make_pool(cluster, accel, topo, name)
    return cluster


def _bind(cluster, name, *, pool="pool-a", shape=(2, 2, 2), accel="v4",
          queued_at=None, bound_at=1.0, ns=NS):
    slices = [{
        "pool": pool, "accelerator": accel, "shape": list(shape),
        "offset": [0, 0, 0], "poolTopology": "2x2x4", "nodes": [],
    }]
    anns = {
        sched.PLACEMENT_ANNOTATION: sched.encode_placement(slices, bound_at)
    }
    if queued_at is not None:
        anns[sched.QUEUED_AT_ANNOTATION] = str(queued_at)
    cluster.patch("Notebook", name, ns, {"metadata": {"annotations": anns}})


def _running(cluster, name, ns=NS):
    cluster.patch("Notebook", name, ns, {"metadata": {"annotations": {
        tl.TIMELINE_ANNOTATION: tl.encode_marks(
            {"requestedAt": 1.0, "runningAt": 2.0}
        )}}})


def _mk(cluster, clock, **kw):
    kw.setdefault("interval_s", 1.0)
    return FleetEfficiencyLedger(cluster, LedgerMetrics(), clock=clock, **kw)


def _pool_ms(ledger, pool="pool-a"):
    return ledger.pool_totals[pool]


class TestClassification:
    def test_ranking(self):
        assert classify_gang(
            {"suspendReason": sess.REASON_PREEMPTION, "stopped": True,
             "state": None, "running": True}
        ) == BUCKET_SUSPENDING
        assert classify_gang(
            {"suspendReason": sess.REASON_STOP, "stopped": False,
             "state": None, "running": True}
        ) == BUCKET_DRAINING
        assert classify_gang(
            {"suspendReason": None, "stopped": True,
             "state": None, "running": True}
        ) == BUCKET_DRAINING
        assert classify_gang(
            {"suspendReason": None, "stopped": False,
             "state": sess.STATE_RESUMING, "running": True}
        ) == BUCKET_STARTING
        assert classify_gang(
            {"suspendReason": None, "stopped": False,
             "state": None, "running": False}
        ) == BUCKET_STARTING
        assert classify_gang(
            {"suspendReason": None, "stopped": False,
             "state": None, "running": True}
        ) == "running"


class TestAttribution:
    def test_empty_pool_time_is_free_usable(self):
        cluster = _world()
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(10)
        led.tick(force=True)
        b = _pool_ms(led)
        # 2x2x4 = 16 chips, one contiguous hole
        assert b[BUCKET_FREE_USABLE] == 16 * 10_000
        assert sum(b.values()) == led.capacity_totals["pool-a"]
        assert led.audit() == []

    def test_first_tick_only_anchors(self):
        cluster = _world()
        led = _mk(cluster, FakeClock())
        assert led.tick(force=True) == 0
        assert led.pool_totals == {}

    def test_bound_not_running_is_starting(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(5)
        led.tick(force=True)
        b = _pool_ms(led)
        assert b[BUCKET_STARTING] == 8 * 5_000
        assert b[BUCKET_FREE_USABLE] == 8 * 5_000
        assert led.ns_totals[NS][BUCKET_STARTING] == 8 * 5_000
        assert led.audit() == []

    def test_running_without_telemetry_is_idle_allocated(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        _running(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(7)
        led.tick(force=True)
        b = _pool_ms(led)
        assert b[BUCKET_BUSY] == 0
        assert b[BUCKET_IDLE] == 8 * 7_000
        assert led.audit() == []

    def test_duty_cycle_splits_busy_idle_exactly(self):
        """An awkward duty (1/3) over many ticks: the residual construction
        keeps busy + idle == chips·dt in integers at every step — the sum is
        exactly the capacity integral, never epsilon-close to it."""
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        _running(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock, telemetry=FakeTelemetry({"nb": 1 / 3}))
        led.tick(force=True)
        for _ in range(37):
            clock.advance(1.7)  # non-integral seconds: ms quantization
            led.tick(force=True)
        b = _pool_ms(led)
        assert b[BUCKET_BUSY] > 0 and b[BUCKET_IDLE] > 0
        assert sum(b.values()) == led.capacity_totals["pool-a"]
        assert led.audit() == []
        eff = led.fleet_efficiency()
        assert 0.30 < eff < 0.37  # ≈ 1/3, quantized per tick

    def test_suspend_reason_buckets(self):
        cluster = _world()
        for name, reason in (
            ("nb-p", sess.REASON_PREEMPTION), ("nb-s", sess.REASON_STOP)
        ):
            cluster.create(api.notebook(
                name, NS, tpu_accelerator="v4", tpu_topology="2x2x1"))
            _bind(cluster, name, shape=(2, 2, 1),
                  pool="pool-a")
        # distinct offsets so both placements replay into the fleet
        nb = cluster.get("Notebook", "nb-s", NS)
        placement = sched.placement_of(nb)
        placement["slices"][0]["offset"] = [0, 0, 1]
        cluster.patch("Notebook", "nb-s", NS, {"metadata": {"annotations": {
            sched.PLACEMENT_ANNOTATION: sched.encode_placement(
                placement["slices"], 1.0)}}})
        for name, reason in (
            ("nb-p", sess.REASON_PREEMPTION), ("nb-s", sess.REASON_STOP)
        ):
            cluster.patch("Notebook", name, NS, {"metadata": {"annotations": {
                sess.SUSPEND_ANNOTATION: sess.encode_suspend_request(
                    reason, 1_000_000.0, 120.0)}}})
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(4)
        led.tick(force=True)
        b = _pool_ms(led)
        # 2x2x1 requests 4 chips but reserve a whole v4 host block (2x2x1
        # chips/host => 4 chips/host, 1 cell)
        assert b[BUCKET_SUSPENDING] == 4 * 4_000
        assert b[BUCKET_DRAINING] == 4 * 4_000
        assert led.audit() == []

    def test_parked_and_queued_are_demand_side(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb-q", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        cluster.patch("Notebook", "nb-q", NS, {"metadata": {"annotations": {
            sched.QUEUED_AT_ANNOTATION: "999.0"}}})
        cluster.create(api.notebook(
            "nb-park", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        cluster.patch("Notebook", "nb-park", NS, {"metadata": {"annotations": {
            sess.STATE_ANNOTATION: sess.STATE_SUSPENDED,
            api.STOP_ANNOTATION: "2026-01-01T00:00:00Z"}}})
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(10)
        led.tick(force=True)
        # demand-side series accrue...
        assert led.queued_totals["v4"] == 8 * 10_000
        assert led.ns_totals[NS][BUCKET_PARKED] == 8 * 10_000
        # ...but hold no pool chips: the pool is entirely free
        b = _pool_ms(led)
        assert b[BUCKET_FREE_USABLE] == 16 * 10_000
        assert led.unmet_demand_chips() == 8.0
        assert led.audit() == []

    def test_resuming_session_is_demand_not_headroom(self):
        """A suspended session resuming into a full fleet: placement gone,
        ack still present, queued-at re-stamped. Its chips are unmet DEMAND
        — never simultaneously parked headroom, or the oversubscription
        decision would lend out the chips the resume is about to reclaim."""
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        cluster.patch("Notebook", "nb", NS, {"metadata": {"annotations": {
            sched.QUEUED_AT_ANNOTATION: "999.0",
            sess.SNAPSHOT_ANNOTATION: sess.encode_snapshot_record(
                "sid1", "d" * 64, 999.5, queued_at=999.0),
            sess.STATE_ANNOTATION: sess.STATE_RESUMING,
        }}})
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(10)
        led.tick(force=True)
        assert led.queued_totals["v4"] == 8 * 10_000
        assert led.ns_totals.get(NS) is None  # no parked chip-seconds
        assert led.unmet_demand_chips() == 8.0
        assert led._journal[-1]["parkedChips"] == 0
        assert led.audit() == []

    def test_drained_host_is_unavailable_and_conserves(self):
        cluster = _world(pools=(("v4", "2x2x2", "pool-a"),))  # 2 hosts
        cluster.patch("Node", "pool-a-1", "", {
            "spec": {"unschedulable": True}})
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(6)
        led.tick(force=True)
        b = _pool_ms(led)
        assert b[BUCKET_UNAVAILABLE] == 4 * 6_000  # one 4-chip host blocked
        assert b[BUCKET_FREE_USABLE] == 4 * 6_000
        assert sum(b.values()) == led.capacity_totals["pool-a"]
        assert led.audit() == []

    def test_fragmentation_strands_free_chips(self):
        # 4x4x4 v4 pool = 16 hosts; occupy the middle so free space shatters
        cluster = _world(pools=(("v4", "4x4x4", "pool-a"),))
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x4"))
        slices = [{
            "pool": "pool-a", "accelerator": "v4", "shape": [2, 2, 4],
            "offset": [2, 0, 0], "poolTopology": "4x4x4", "nodes": [],
        }]
        cluster.patch("Notebook", "nb", NS, {"metadata": {"annotations": {
            sched.PLACEMENT_ANNOTATION: sched.encode_placement(slices, 1.0),
        }}})
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(3)
        led.tick(force=True)
        b = _pool_ms(led)
        free_ms = b[BUCKET_FREE_USABLE] + b[BUCKET_FREE_STRANDED]
        assert free_ms == (64 - 16) * 3_000
        assert b[BUCKET_FREE_STRANDED] > 0  # the carve split the torus
        assert sum(b.values()) == led.capacity_totals["pool-a"]
        assert led.audit() == []

    def test_placement_into_vanished_pool_claims_nothing(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb", pool="pool-gone")
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(5)
        led.tick(force=True)
        b = _pool_ms(led)
        assert b[BUCKET_FREE_USABLE] == 16 * 5_000
        assert led.ns_totals.get(NS) is None
        assert led.audit() == []


class TestExactlyOnce:
    def test_intervals_contiguous_across_ticks(self):
        cluster = _world()
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        for dt in (1.0, 0.25, 13.37, 45.0):
            clock.advance(dt)
            led.tick(force=True)
        spans = [(r["t0Ms"], r["t1Ms"]) for r in led._journal]
        assert all(a[1] == b[0] for a, b in zip(spans, spans[1:]))
        assert led.audit() == []

    def test_zero_elapsed_tick_is_a_noop(self):
        cluster = _world()
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        assert led.tick(force=True) == 0  # same instant: nothing to claim
        clock.advance(2)
        assert led.tick(force=True) == 2_000
        assert led.audit() == []

    def test_interval_gating(self):
        cluster = _world()
        clock = FakeClock()
        led = _mk(cluster, clock, interval_s=10.0)
        led.tick(force=True)
        clock.advance(3)
        assert led.tick() == 0        # inside the interval: gated
        clock.advance(8)
        assert led.tick() == 11_000   # one interval covers both advances
        assert led.audit() == []


class TestAuditCatchesPlants:
    def _ledger_with_history(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        _running(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock, telemetry=FakeTelemetry({"nb": 0.5}))
        led.tick(force=True)
        for _ in range(3):
            clock.advance(5)
            led.tick(force=True)
        assert led.audit() == []
        return led

    def test_planted_class_flip_fails(self):
        led = self._ledger_with_history()
        led._journal[-1]["gangs"][0]["class"] = BUCKET_DRAINING
        assert any("misattribution" in v for v in led.audit())

    def test_planted_bucket_value_fails_conservation(self):
        led = self._ledger_with_history()
        led._journal[-1]["pools"]["pool-a"]["buckets"][BUCKET_BUSY] += 1
        assert any("CONSERVATION" in v for v in led.audit())

    def test_planted_chip_inflation_fails_geometry(self):
        led = self._ledger_with_history()
        led._journal[-1]["gangs"][0]["chipsByPool"]["pool-a"] += 8
        assert any("slice geometry" in v for v in led.audit())

    def test_planted_busy_skew_fails_duty_reproof(self):
        led = self._ledger_with_history()
        g = led._journal[-1]["gangs"][0]
        g["busyMs"] += 1
        assert any("duty-weighted" in v for v in led.audit())

    def test_planted_interval_gap_fails_exactly_once(self):
        led = self._ledger_with_history()
        led._journal[-1]["t0Ms"] += 5
        out = led.audit()
        assert any("leaks" in v for v in out)

    def test_planted_overlap_fails_exactly_once(self):
        led = self._ledger_with_history()
        led._journal[-1]["t0Ms"] -= 5
        assert any("overlaps" in v for v in led.audit())

    def test_cumulative_totals_cross_checked_against_journal(self):
        led = self._ledger_with_history()
        led.pool_totals["pool-a"][BUCKET_BUSY] += 10
        led.capacity_totals["pool-a"] += 10  # keep conservation consistent
        out = led.audit()
        assert any("journal replay" in v for v in out)


class TestExports:
    def test_registry_families_equal_internal_ledger(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        _running(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock, telemetry=FakeTelemetry({"nb": 0.25}))
        led.tick(force=True)
        clock.advance(9)
        led.tick(force=True)
        m = led.metrics
        for bucket in CONSERVATION_BUCKETS:
            assert m.pool_chip_seconds.get(
                pool="pool-a", bucket=bucket
            ) == led.pool_totals["pool-a"][bucket] / 1000.0
        assert m.capacity_chip_seconds.get(
            pool="pool-a"
        ) == led.capacity_totals["pool-a"] / 1000.0
        # the exposition parses (the dynamic half of metrics-lint)
        text = m.registry.expose()
        assert "tpu_pool_chip_seconds_total" in text
        assert "tpu_capacity_chip_seconds_total" in text
        assert "tpu_fleet_efficiency" in text

    def test_notebook_payload_and_namespace_drilldown(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        _running(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock, telemetry=FakeTelemetry({"nb": 0.5}))
        led.tick(force=True)
        clock.advance(10)
        led.tick(force=True)
        p = led.notebook_payload(NS, "nb")
        assert p["busyChipSeconds"] == 40.0         # 8 chips × 10 s × 0.5
        assert p["allocatedChipSeconds"] == 80.0
        assert p["efficiency"] == 0.5
        assert led.notebook_payload(NS, "ghost") is None
        nsp = led.namespace_payload(NS)
        assert nsp["efficiency"] == 0.5
        assert "nb" in nsp["notebooks"]
        assert led.namespace_payload("ghost-ns") is None

    def test_departed_notebook_accumulator_evicted(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(5)
        led.tick(force=True)
        assert led.notebook_payload(NS, "nb") is not None
        cluster.delete("Notebook", "nb", NS)
        clock.advance(5)
        led.tick(force=True)
        assert led.notebook_payload(NS, "nb") is None

    def test_debug_routes(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(5)
        led.tick(force=True)
        app = App("probes", csrf_protect=False)
        install_ledger_routes(app, led)
        client = Client(app)
        payload = json.loads(client.get("/debug/ledger").data)
        assert payload["pools"]["pool-a"]["capacityChipSeconds"] == 80.0
        assert payload["fleet"]["wasteFraction"] >= 0.0
        ns_payload = json.loads(client.get(f"/debug/ledger/{NS}").data)
        assert ns_payload["namespace"] == NS
        assert client.get("/debug/ledger/ghost-ns").status_code == 404


class TestShardedWiring:
    def test_only_shard_zero_runs_the_ledger(self):
        """One ledger per FLEET: in the sharded control plane only shard
        0's manager carries one — its tick reads the whole cluster, so a
        ledger per shard leader would export every chip-second N times
        while the conservation ratio still read exactly 1."""
        from kubeflow_tpu.cmd.controller import build_manager
        from kubeflow_tpu.runtime.sharding import ShardRouter
        from kubeflow_tpu.utils.config import ControllerConfig

        cluster = FakeCluster()
        cfg = ControllerConfig(ledger_enabled=True)
        router = ShardRouter(4)
        shared: dict = {}
        managers = [
            build_manager(
                cluster, cfg, fetch_kernels=lambda ns, n: [],
                router=router, shard_id=i, shared=shared,
            )[0]
            for i in range(4)
        ]
        ledgers = [m.ledger for m in managers]
        assert ledgers[0] is not None
        assert all(led is ledgers[0] for led in ledgers)  # one singleton
        # the one-process-per-shard layout: a non-zero shard alone builds NO
        # ledger at all
        solo, _ = build_manager(
            cluster, cfg, fetch_kernels=lambda ns, n: [],
            router=router, shard_id=2, shared={},
        )
        assert solo.ledger is None
        zero, _ = build_manager(
            cluster, cfg, fetch_kernels=lambda ns, n: [],
            router=router, shard_id=0, shared={},
        )
        assert zero.ledger is not None


class TestWebSurfaces:
    def _ledgered_world(self):
        cluster = _world()
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        _running(cluster, "nb")
        clock = FakeClock()
        led = _mk(cluster, clock, telemetry=FakeTelemetry({"nb": 0.5}))
        led.tick(force=True)
        clock.advance(10)
        led.tick(force=True)
        return cluster, led

    def test_jwa_detail_carries_efficiency(self):
        from kubeflow_tpu.auth.rbac import Authorizer
        from kubeflow_tpu.webapps import jupyter

        cluster, led = self._ledgered_world()
        app = jupyter.create_app(
            cluster, ledger=led, use_cache=False,
            authorizer=Authorizer(
                cluster, cluster_admins={"admin@example.com"}
            ),
        )
        client = Client(app)
        r = client.get(
            f"/api/namespaces/{NS}/notebooks/nb",
            headers={"kubeflow-userid": "admin@example.com"},
        )
        body = json.loads(r.data)
        eff = body["notebook"]["efficiency"]
        assert eff["efficiency"] == 0.5
        assert eff["busyChipSeconds"] == 40.0

    def test_dashboard_serves_ledger_series(self):
        from kubeflow_tpu.webapps import dashboard

        cluster, led = self._ledgered_world()
        app = dashboard.create_app(
            cluster, ledger=led, cluster_admins={"admin@example.com"},
            use_cache=False,
        )
        app.close()
        client = Client(app)
        for mtype in ("efficiency", "waste", "unmet_demand"):
            r = client.get(
                f"/api/metrics/{mtype}",
                headers={"kubeflow-userid": "admin@example.com"},
            )
            assert r.status_code == 200, (mtype, r.data)
            body = json.loads(r.data)
            assert "series" in body
        eff = json.loads(client.get(
            "/api/metrics/efficiency",
            headers={"kubeflow-userid": "admin@example.com"},
        ).data)
        assert eff["values"][0]["value"] == 0.5


class TestPoolDeathEdgeWindows:
    """Pools that vanish mid-interval (a spot revocation kill, capacity/)
    must close their buckets at the last observation before death — the
    sampling contract: an interval is attributed to the fleet observed at
    its right edge, so a dead pool accumulates nothing further, its
    capacity integral freezes with its buckets, and conservation stays
    exact-integer through death AND rebirth. One test per lifecycle state
    the kill can interrupt (starting, the suspend barrier, free_usable)."""

    def _kill_pool(self, cluster, pool="pool-a"):
        for node in list(cluster.list("Node")):
            name = node["metadata"]["name"]
            if name.startswith(f"{pool}-"):
                cluster.delete("Node", name)

    def _frozen_after_death(self, cluster, clock, led, pool="pool-a"):
        """Kill the pool mid-interval; prove its books freeze and stay
        conserved."""
        before = dict(_pool_ms(led, pool))
        cap_before = led.capacity_totals[pool]
        clock.advance(0.5)
        self._kill_pool(cluster, pool)
        clock.advance(0.5)
        led.tick(force=True)
        assert _pool_ms(led, pool) == before  # closed at the death edge
        assert led.capacity_totals[pool] == cap_before
        assert sum(before.values()) == cap_before  # conservation, frozen
        clock.advance(5.0)
        led.tick(force=True)
        assert _pool_ms(led, pool) == before  # stays closed
        assert led.audit() == []

    def test_death_during_starting(self):
        cluster = _world()
        clock = FakeClock()
        led = _mk(cluster, clock)
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")  # bound, no runningAt mark: starting
        led.tick(force=True)
        clock.advance(2.0)
        led.tick(force=True)
        assert _pool_ms(led)[BUCKET_STARTING] == 8 * 2000
        self._frozen_after_death(cluster, clock, led)

    def test_death_during_suspend_barrier(self):
        cluster = _world()
        clock = FakeClock()
        led = _mk(cluster, clock)
        cluster.create(api.notebook(
            "nb", NS, tpu_accelerator="v4", tpu_topology="2x2x2"))
        _bind(cluster, "nb")
        _running(cluster, "nb")
        cluster.patch("Notebook", "nb", NS, {"metadata": {"annotations": {
            sess.SUSPEND_ANNOTATION: sess.encode_suspend_request(
                sess.REASON_PREEMPTION, 1_000_000.0, 60.0
            )}}})
        led.tick(force=True)
        clock.advance(2.0)
        led.tick(force=True)
        assert _pool_ms(led)[BUCKET_SUSPENDING] == 8 * 2000
        self._frozen_after_death(cluster, clock, led)

    def test_death_during_free_usable(self):
        cluster = _world()
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(2.0)
        led.tick(force=True)
        assert _pool_ms(led)[BUCKET_FREE_USABLE] == 16 * 2000
        self._frozen_after_death(cluster, clock, led)

    def test_rebirth_resumes_without_double_counting(self):
        cluster = _world()
        clock = FakeClock()
        led = _mk(cluster, clock)
        led.tick(force=True)
        clock.advance(2.0)
        led.tick(force=True)
        frozen = dict(_pool_ms(led))
        self._kill_pool(cluster)
        clock.advance(3.0)
        led.tick(force=True)
        assert _pool_ms(led) == frozen  # the dead window attributes nothing
        # the pool returns (same name — a re-provisioned replacement)
        make_pool(cluster, "v4", "2x2x4", "pool-a")
        clock.advance(2.0)
        led.tick(force=True)
        after = _pool_ms(led)
        # the 3s dead window stays unattributed; only the 2s since rebirth
        # was observed at this tick's right edge accrues... the rebirth tick
        # itself attributes its whole interval to the reborn fleet
        assert after[BUCKET_FREE_USABLE] == frozen[BUCKET_FREE_USABLE] + 16 * 2000
        assert sum(after.values()) == led.capacity_totals["pool-a"]
        assert led.audit() == []
